"""Metadata store: CRUD, publish, cache queries, lineage."""

import os

import pytest

from tpu_pipelines.metadata import (
    Artifact,
    ArtifactState,
    Context,
    Execution,
    ExecutionState,
    MetadataStore,
)
from tpu_pipelines.utils.fingerprint import (
    execution_cache_key,
    fingerprint_dir,
    fingerprint_callable,
)


def _make_store(backend: str, path: str = ":memory:"):
    if backend == "native":
        from tpu_pipelines.metadata.native_store import (
            NativeMetadataStore,
            NativeUnavailable,
        )

        try:
            return NativeMetadataStore(path)
        except NativeUnavailable as e:
            pytest.skip(f"native backend unavailable: {e}")
    return MetadataStore(path)


@pytest.fixture(params=["python", "native"])
def store(request):
    s = _make_store(request.param)
    yield s
    s.close()


def test_artifact_roundtrip(store):
    art = Artifact(type_name="Examples", uri="/tmp/x", properties={"splits": ["train", "eval"]})
    aid = store.put_artifact(art)
    assert aid == art.id > 0
    back = store.get_artifact(aid)
    assert back.type_name == "Examples"
    assert back.properties == {"splits": ["train", "eval"]}
    assert back.state == ArtifactState.PENDING

    art.state = ArtifactState.LIVE
    store.put_artifact(art)
    assert store.get_artifact(aid).state == ArtifactState.LIVE
    assert store.get_artifacts(type_name="Examples")[0].id == aid
    assert store.get_artifacts_by_uri("/tmp/x")[0].id == aid
    assert store.get_artifact(999) is None


def test_execution_roundtrip(store):
    ex = Execution(type_name="Trainer", node_id="Trainer", cache_key="k1")
    store.put_execution(ex)
    assert ex.id > 0
    ex.state = ExecutionState.COMPLETE
    ex.properties["examples_per_sec"] = 123.0
    store.put_execution(ex)
    back = store.get_execution(ex.id)
    assert back.state == ExecutionState.COMPLETE
    assert back.properties["examples_per_sec"] == 123.0
    assert store.get_executions(node_id="Trainer")[0].id == ex.id


def test_publish_execution_and_lineage(store):
    raw = Artifact(type_name="Examples", uri="/tmp/examples")
    store.put_artifact(raw)
    gen = Execution(
        type_name="ExampleGen", node_id="ExampleGen",
        state=ExecutionState.COMPLETE,
    )
    store.publish_execution(gen, {}, {"examples": [raw]})
    assert store.get_artifact(raw.id).state == ArtifactState.LIVE

    model = Artifact(type_name="Model", uri="/tmp/model")
    train = Execution(
        type_name="Trainer", node_id="Trainer", state=ExecutionState.COMPLETE
    )
    store.publish_execution(
        train, {"examples": [raw]}, {"model": [model]},
        contexts=[Context("pipeline_run", "run-1")],
    )

    lineage = store.get_lineage(model.id)
    assert lineage.artifact.id == model.id
    assert lineage.producer.type_name == "Trainer"
    assert lineage.parents[0].artifact.id == raw.id
    assert lineage.parents[0].producer.type_name == "ExampleGen"

    txt = store.format_lineage(model.id)
    assert "Model" in txt and "Trainer" in txt and "ExampleGen" in txt

    ctx = store.get_context("pipeline_run", "run-1")
    assert ctx is not None
    assert [e.id for e in store.get_executions_by_context(ctx.id)] == [train.id]
    assert [a.id for a in store.get_artifacts_by_context(ctx.id)] == [model.id]


def test_failed_execution_abandons_outputs(store):
    out = Artifact(type_name="Model", uri="/tmp/m2")
    ex = Execution(
        type_name="Trainer", node_id="Trainer", state=ExecutionState.FAILED
    )
    store.publish_execution(ex, {}, {"model": [out]})
    assert store.get_artifact(out.id).state == ArtifactState.ABANDONED


def test_cache_hit_and_miss(store):
    out = Artifact(type_name="Model", uri="/tmp/m", fingerprint="fp1")
    ex = Execution(
        type_name="Trainer", node_id="Trainer",
        state=ExecutionState.COMPLETE, cache_key="key-abc",
    )
    store.publish_execution(ex, {}, {"model": [out]})

    hit = store.get_cached_outputs("key-abc")
    assert hit is not None
    assert [a.id for a in hit["model"]] == [out.id]
    assert store.get_cached_outputs("other") is None
    assert store.get_cached_outputs("") is None

    # A cached-output artifact that was GC'd invalidates the entry.
    out.state = ArtifactState.DELETED
    store.put_artifact(out)
    assert store.get_cached_outputs("key-abc") is None


def test_cache_key_sensitivity():
    base = dict(
        node_id="Trainer",
        executor_version="v1",
        exec_properties={"steps": 100},
        input_fingerprints={"examples": ["fp-a"]},
    )
    k0 = execution_cache_key(**base)
    assert k0 == execution_cache_key(**base)
    assert k0 != execution_cache_key(**{**base, "executor_version": "v2"})
    assert k0 != execution_cache_key(**{**base, "exec_properties": {"steps": 101}})
    assert k0 != execution_cache_key(
        **{**base, "input_fingerprints": {"examples": ["fp-b"]}}
    )
    assert k0 != execution_cache_key(**{**base, "node_id": "Trainer2"})


def test_fingerprint_dir_content_sensitive(tmp_path):
    d = tmp_path / "art"
    d.mkdir()
    (d / "a.txt").write_text("hello")
    fp1 = fingerprint_dir(str(d))
    assert fp1 == fingerprint_dir(str(d))
    (d / "a.txt").write_text("world")
    assert fingerprint_dir(str(d)) != fp1
    (d / "b.txt").write_text("x")
    fp3 = fingerprint_dir(str(d))
    os.rename(d / "b.txt", d / "c.txt")
    assert fingerprint_dir(str(d)) != fp3


def test_fingerprint_callable_tracks_source():
    def f(x):
        return x + 1

    def g(x):
        return x + 2

    assert fingerprint_callable(f) != fingerprint_callable(g)
    assert fingerprint_callable(f) == fingerprint_callable(f)


def test_file_backed_store_persists(tmp_path):
    path = str(tmp_path / "md.sqlite")
    s1 = MetadataStore(path)
    art = Artifact(type_name="Schema", uri="/tmp/s")
    s1.put_artifact(art)
    s1.close()
    s2 = MetadataStore(path)
    assert s2.get_artifact(art.id).type_name == "Schema"
    s2.close()


def test_complete_execution_without_outputs_is_not_cache_hit(store):
    ex = Execution(
        type_name="T", node_id="T", state=ExecutionState.COMPLETE,
        cache_key="orphan",
    )
    store.put_execution(ex)  # no events published (simulates corrupt state)
    assert store.get_cached_outputs("orphan") is None
